"""Combinatorial markets + adaptive belief propagation (round 18).

Round 12's graph sweep carried point values through a fixed number of
damped iterations. The round-18 ``infer/`` tier upgrades the workload
in three moves, shown here end to end:

1. **Constraint-typed blocks** — a 4-way election is declared as ONE
   ``mutually_exclusive`` block and a 2-leg parlay as one ``implies``
   block; ``MarketBlocks.to_graph()`` compiles the constraints to the
   MarketGraph edges the device sweep consumes. No hand-wired edges.
2. **Moment-pair adaptive BP** — ``InferenceOptions`` switches the
   sweep to (mean, variance) pairs with a deterministic early-exit:
   the sweep runs until max |Δmean| dips under ``tol`` (device-resident
   residual, bit-stable trip count on every mesh factorisation) instead
   of a fixed step budget.
3. **Deterministic projection** — after the sweep, the election's
   outcomes are renormalised to SUM TO 1 and the parlay's composite is
   clamped to its tightest leg — host-side, pure, order-independent.
4. The byte-exactness coda: the identical batch settled WITHOUT
   analytics produces the identical point consensus and identical
   store bytes — blocks + BP + projection are pure-additive reads
   (tests/test_infer.py pins the journal/SQLite matrix).

Run from the repo root:  python examples/combinatorial_markets.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from bayesian_consensus_engine_tpu.analytics import AnalyticsOptions
from bayesian_consensus_engine_tpu.infer import (
    InferenceOptions,
    MarketBlock,
    MarketBlocks,
)
from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
from bayesian_consensus_engine_tpu.pipeline import (
    ShardedSettlementSession,
    build_settlement_plan,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

NOW = 21_900.0

# ---------------------------------------------------------------------------
# Act 1 — the combinatorial scenario, declared as constraints.
# ---------------------------------------------------------------------------
# A 4-way election (exactly one candidate wins) where the sources
# overprice the field — the raw consensus sums well past 1 — plus a
# 2-leg parlay whose composite the sources price ABOVE one of its legs
# (an arbitrage the implication constraint forbids).
payloads = [
    ("cand-a", [
        {"sourceId": f"s-{i}", "probability": p}
        for i, p in enumerate([0.45, 0.50, 0.48])
    ]),
    ("cand-b", [
        {"sourceId": f"s-{i}", "probability": p}
        for i, p in enumerate([0.35, 0.32, 0.30])
    ]),
    ("cand-c", [
        {"sourceId": f"s-{i}", "probability": p}
        for i, p in enumerate([0.22, 0.25, 0.20])
    ]),
    ("cand-d", [
        {"sourceId": f"s-{i}", "probability": p}
        for i, p in enumerate([0.10, 0.12, 0.08])
    ]),
    ("parlay", [
        {"sourceId": f"s-{i}", "probability": p}
        for i, p in enumerate([0.50, 0.55])
    ]),
    ("leg-1", [
        {"sourceId": f"s-{i}", "probability": p}
        for i, p in enumerate([0.62, 0.60])
    ]),
    ("leg-2", [
        {"sourceId": f"s-{i}", "probability": p}
        for i, p in enumerate([0.40, 0.38])
    ]),
]
outcomes = [True, False, False, False, False, True, False]

blocks = MarketBlocks([
    MarketBlock(
        "mutually_exclusive", ("cand-a", "cand-b", "cand-c", "cand-d")
    ),
    MarketBlock("implies", ("parlay", "leg-1", "leg-2")),
])

mesh = make_mesh()
store = TensorReliabilityStore()
plan = build_settlement_plan(store, payloads, num_slots=8)

with ShardedSettlementSession(store, plan, mesh) as session:
    result, tiebreak, bands, prop = session.settle_with_analytics(
        outcomes, steps=2, now=NOW,
        analytics=AnalyticsOptions(
            blocks=blocks,
            inference=InferenceOptions(
                tol=2e-2, max_steps=16, damping=0.2
            ),
        ),
    )

keys = result.market_keys
consensus = np.asarray(result.consensus)
mean = np.asarray(prop.mean)
stderr = np.asarray(prop.stderr)

print("constraint blocks → graph edges → adaptive BP → projection\n")
print(f"{'market':>8}  {'consensus':>9}  {'projected':>9}  {'stderr':>7}")
for row, key in enumerate(keys):
    print(
        f"{key:>8}  {consensus[row]:9.4f}  {mean[row]:9.4f}  "
        f"{stderr[row]:7.4f}"
    )

# ---------------------------------------------------------------------------
# Act 2 — what the constraints bought.
# ---------------------------------------------------------------------------
cand_rows = [keys.index(k) for k in ("cand-a", "cand-b", "cand-c", "cand-d")]
raw_sum = float(consensus[cand_rows].sum())
proj_sum = float(mean[cand_rows].sum())
assert abs(proj_sum - 1.0) < 1e-6
# The gentle damping + early-exit stop BEFORE the averaging fixed point
# flattens the field: the candidates keep their market-implied ordering.
assert list(mean[cand_rows]) == sorted(mean[cand_rows], reverse=True)
print(
    f"\nelection: raw consensus sums to {raw_sum:.4f} (overpriced field) "
    f"— projected outcomes sum to {proj_sum:.4f}\nwith the ordering "
    "intact. Exactly-one-winner is a DECLARED invariant, not a hope."
)

parlay, leg1, leg2 = (keys.index(k) for k in ("parlay", "leg-1", "leg-2"))
assert mean[parlay] <= mean[leg1] + 1e-6
assert mean[parlay] <= mean[leg2] + 1e-6
print(
    f"parlay: priced {consensus[parlay]:.4f} vs legs "
    f"{consensus[leg1]:.4f}/{consensus[leg2]:.4f} — the implication "
    f"clamp settles it at {mean[parlay]:.4f}\n(a conjunction can never "
    "beat its weakest leg)."
)
assert int(prop.iters_run) < 16
print(
    f"adaptive BP converged in {int(prop.iters_run)} sweeps "
    f"(residual {float(prop.residual):.2e} <= tol 2e-02, bound 16) — "
    "the trip count is a pure\nfunction of the inputs, identical on "
    "every mesh factorisation."
)

# ---------------------------------------------------------------------------
# Act 3 — the byte-exactness coda: the settle never felt any of it.
# ---------------------------------------------------------------------------
plain_store = TensorReliabilityStore()
plain_plan = build_settlement_plan(plain_store, payloads, num_slots=8)
with ShardedSettlementSession(plain_store, plain_plan, mesh) as plain:
    plain_result = plain.settle(outcomes, steps=2, now=NOW)

np.testing.assert_array_equal(
    consensus, np.asarray(plain_result.consensus)
)
rows = np.arange(plain_store.live_row_count())
for got, want in zip(store.host_rows(rows), plain_store.host_rows(rows)):
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
print(
    "\ncoda: point consensus and stored reliability state are "
    "BIT-IDENTICAL with\nblocks+BP on or off — constraints, sweep, and "
    "projection are pure-additive reads.\nbench.py --leg e2e_infer "
    "carries the adaptive-vs-fixed sweep-count capture."
)
