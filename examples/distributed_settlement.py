"""Multi-host settlement layout, demonstrated on a virtual device mesh.

The production topology: markets split across hosts (DCN-outer — zero
cross-market traffic rides the slow wire), each host feeds ONLY its own
market band into a globally-sharded array, the cycle's sources-axis psum
stays on ICI, and each host reads back and checkpoints only its own band
(e.g. one SQLite shard per host). This demo runs the whole flow
single-process on 8 virtual CPU devices; on a real pod the same code runs
per-process after ``init_distributed(coordinator_address=...)`` — see
tests/test_distributed_multiprocess.py for a real two-process cluster.

Run: python examples/distributed_settlement.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import jax.numpy as jnp  # noqa: E402

from bayesian_consensus_engine_tpu.parallel import (  # noqa: E402
    MarketBlockState,
    build_cycle_loop,
    init_block_state,
    init_distributed,
    local_view,
    make_hybrid_mesh,
    process_market_rows,
)
from bayesian_consensus_engine_tpu.parallel.distributed import (  # noqa: E402
    global_block,
    global_market,
)


def main() -> None:
    info = init_distributed()  # no-op single-process; joins a cluster on a pod
    print(f"process {info['process_index']}/{info['process_count']}, "
          f"{info['global_devices']} devices")

    # 2 granules of 4 devices: markets axis = 2 x 2, sources axis = 2.
    mesh = make_hybrid_mesh(ici_shape=(2, 2), num_granules=2)
    markets, slots, steps = 64, 8, 5

    lo, hi = process_market_rows(markets, mesh)
    print(f"this process owns market rows [{lo}, {hi})")

    # Each host materialises ONLY its band (here: one process owns all).
    rng = np.random.default_rng(0)
    probs_band = rng.random((hi - lo, slots)).astype(np.float32)
    mask_band = rng.random((hi - lo, slots)) < 0.9
    outcome_band = rng.random(hi - lo) < 0.5

    probs = global_block(probs_band, mesh, markets)
    mask = global_block(mask_band, mesh, markets)
    outcome = global_market(outcome_band, mesh, markets)
    # Band-sized cold state built directly — no process ever allocates the
    # global block (cold-start rows are the same constants everywhere).
    state = MarketBlockState(
        *(
            global_block(np.asarray(x), mesh, markets)
            for x in init_block_state(hi - lo, slots)
        )
    )

    loop = build_cycle_loop(mesh, slot_major=False, donate=True)
    state, consensus = loop(probs, mask, outcome, state, jnp.float32(1.0), steps)

    # Read back ONLY this host's band — no global gather anywhere.
    my_consensus = local_view(consensus)
    my_reliability = local_view(state.reliability)
    print(f"{steps} cycles over {markets} markets on {mesh.shape} mesh")
    print(f"  my band consensus[:4] = {np.asarray(my_consensus)[:4].round(4)}")
    print(f"  my reliability band shape = {my_reliability.shape} "
          f"(flush this to the host-local SQLite shard)")

    # ---- the same topology through the pipeline layer -------------------
    # Raw payloads → per-band plan (this process packs ONLY its own
    # payload shard, with the globally-agreed slot height) → chained
    # device-resident settles → a band-local store any host read syncs.
    from bayesian_consensus_engine_tpu.pipeline import (
        ShardedSettlementSession,
        build_settlement_plan,
    )
    from bayesian_consensus_engine_tpu.state.tensor_store import (
        TensorReliabilityStore,
    )

    band_payloads = [
        (
            f"market-{m}",
            [
                {
                    "sourceId": f"s{int(rng.integers(0, 12))}",
                    "probability": float(rng.random()),
                }
                for _ in range(int(rng.integers(1, 5)))
            ],
        )
        for m in range(lo, min(hi, markets))
    ]
    store = TensorReliabilityStore()
    plan = build_settlement_plan(store, band_payloads, num_slots=4)
    outcomes = [bool(o) for o in outcome_band]
    with ShardedSettlementSession(
        store, plan, mesh, band=(lo, markets)
    ) as session:
        session.settle(outcomes, steps=2, now=20_900.0)
        final = session.settle(outcomes, steps=1, now=20_901.0)  # chained
    print(f"  session: {len(final.market_keys)} band markets settled twice "
          f"device-resident; {len(store.list_sources())} records in this "
          "host's store shard")


if __name__ == "__main__":
    main()
