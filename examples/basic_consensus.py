"""Minimal library flow: validate a payload, compute consensus, read diagnostics.

Run from the repo root:  python examples/basic_consensus.py
"""

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from bayesian_consensus_engine_tpu.core import (
    compute_consensus,
    validate_input_payload,
)

payload = {
    "schemaVersion": "1.0.0",
    "marketId": "demo-market",
    "signals": [
        {"sourceId": "forecaster-1", "probability": 0.72},
        {"sourceId": "forecaster-2", "probability": 0.65},
        {"sourceId": "model-x", "probability": 0.80},
    ],
}

validate_input_payload(payload)
result = compute_consensus(payload["signals"])

print(json.dumps(result, indent=2))
print()
print(f"Consensus probability: {result['consensus']:.2%}")
print(f"Cold-start sources:    {result['diagnostics']['coldStartSources']}")
