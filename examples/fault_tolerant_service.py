"""A settlement service that survives checkpoint failures mid-stream.

The service contract (pinned by tests/test_overlap.py and the 1.3M-row
soak in scripts/stream_failure_soak.py): when a background checkpoint
dies — disk full, volume detached, another process holding the SQLite
file — ``settle_stream`` surfaces the failure at the next flush join,
the store rolls the flush bookkeeping back (failed rows re-dirtied), and
NO settled batch is lost. This example shows the user-side half of that
contract: the restart recipe.

    completed = 0
    while completed < len(batches):
        stats = []
        try:
            for result in settle_stream(store, batches[completed:],
                                        stats=stats, ...):
                ...
        except OSError/RuntimeError:
            <fix the world>          # free disk, release the lock, ...
            store.flush_to_sqlite(db)  # re-covers everything settled
        completed += len(stats)      # SETTLED count, not yielded count

The resume point is ``len(stats)``, NOT the number of results consumed:
a checkpoint failure aborts the stream AFTER the current batch settled
but BEFORE it yielded, and re-settling that batch would double its
updates. The same ``store`` carries across restarts — interning,
capacity, and deferred state all survive — so the retried stream
continues exactly where the failed one stopped. The failure here is
real: a second SQLite connection takes an exclusive lock on the
checkpoint file mid-stream (the native writer fails with "database is
locked" after its busy timeout), then the service releases it and
resumes.

Run from the repo root:  python examples/fault_tolerant_service.py
"""

import os
import pathlib
import sqlite3
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

from bayesian_consensus_engine_tpu.pipeline import settle_stream  # noqa: E402
from bayesian_consensus_engine_tpu.state.tensor_store import (  # noqa: E402
    TensorReliabilityStore,
)

BATCHES = 5
MARKETS_PER_BATCH = 1_500
START_DAY = 20_820.0

rng = np.random.default_rng(37)


def day_batch(day: int):
    counts = rng.poisson(2, MARKETS_PER_BATCH) + 1
    payloads = []
    for m, count in enumerate(counts):
        signals = [
            {
                "sourceId": f"src-{rng.integers(0, 400)}",
                "probability": round(float(rng.random()), 6),
            }
            for _ in range(count)
        ]
        payloads.append((f"day{day}-market-{m}", signals))
    outcomes = (rng.random(MARKETS_PER_BATCH) < 0.5).tolist()
    return payloads, outcomes


def main() -> None:
    batches = [day_batch(day) for day in range(BATCHES)]
    store = TensorReliabilityStore()
    lock: dict = {}

    def sabotage_after(index):
        """Simulate an external process pinning the checkpoint file."""
        conn = sqlite3.connect(db, check_same_thread=False)
        conn.execute("PRAGMA locking_mode=EXCLUSIVE")
        conn.execute("BEGIN EXCLUSIVE")
        lock["conn"] = conn
        print(f"  [outage] checkpoint file locked after batch {index}")

    def repair():
        conn = lock.pop("conn")
        conn.rollback()
        conn.close()  # EXCLUSIVE locking-mode holds the lock until close
        print("  [repair] lock released; retrying the checkpoint")

    with tempfile.TemporaryDirectory() as tmp:
        global db
        db = os.path.join(tmp, "service.db")

        completed = 0
        restarts = 0
        while completed < len(batches):
            stats: list = []
            try:
                for i, result in enumerate(settle_stream(
                    store,
                    batches[completed:],
                    steps=1,
                    now=START_DAY + completed,
                    db_path=db,
                    stats=stats,
                )):
                    print(
                        f"  batch {completed + i} settled "
                        f"({len(result.market_keys)} markets)"
                    )
                    if completed + i == 1 and not restarts:
                        sabotage_after(completed + i)
            except Exception as exc:
                restarts += 1
                print(f"  [failure] {type(exc).__name__}: {exc}")
                repair()
                # Rollback re-dirtied the failed rows: one retry flush
                # re-covers every batch settled so far.
                store.flush_to_sqlite(db)
            # The settled count — NOT the yielded count: the batch whose
            # checkpoint failed settled without yielding.
            completed += len(stats)

        store.sync()
        rows = sqlite3.connect(db).execute(
            "SELECT COUNT(*) FROM sources"
        ).fetchone()[0]
        live = len(store.list_sources())
        print(
            f"\n{completed} batches settled across {restarts + 1} stream "
            f"runs ({restarts} failure restart); final checkpoint holds "
            f"{rows} rows == store's {live} live records: {rows == live}"
        )
        assert completed == BATCHES and rows == live and restarts == 1

        # The recovered run must equal a never-failed straight-through run
        # record for record — the restart settled each batch exactly once.
        straight = TensorReliabilityStore()
        for _ in settle_stream(straight, batches, steps=1, now=START_DAY):
            pass
        straight.sync()
        assert store.list_sources() == straight.list_sources()
        print("recovered state == straight-through state, record for record")

    journal_recovery()


def journal_recovery() -> None:
    """Act two: PROCESS-DEATH recovery via the durability journal.

    The recipe above survives checkpoint failures inside one process —
    the live ``store`` object carries across restarts. When the process
    itself dies, the journal is the durable truth
    (``settle_stream(journal=...)`` appends one fsynced epoch per
    checkpoint, tagged with the settled batch index): a NEW process
    replays it, resumes from ``tag + 1``, and appends to the SAME
    journal with ``JournalWriter(path, resume=True)``. Rolling SQLite
    flushes aren't needed mid-stream at all — the interchange file is
    exported once at the end (which is also why the journal's service
    rate beat rolling SQLite 1.47x on-chip: docs/API.md).
    """
    from bayesian_consensus_engine_tpu.state.journal import (  # noqa: E402
        JournalWriter,
        replay_journal,
    )

    batches = [day_batch(day) for day in range(BATCHES)]
    with tempfile.TemporaryDirectory() as tmp:
        jrnl = os.path.join(tmp, "service.jrnl")

        # --- process one: dies (we break out) after batch 2's epoch ---
        store = TensorReliabilityStore()
        stream = settle_stream(
            store, batches, steps=1, now=START_DAY, journal=jrnl,
        )
        for i, _result in enumerate(stream):
            if i == 2:
                # Durability came from the per-batch fsynced epochs
                # (checkpoint_every=1 writes each epoch BEFORE its batch
                # yields); close() would only add a tail epoch when
                # checkpoint_every > 1 left settled batches uncovered.
                stream.close()
                del store, stream  # "the process died"
                break
        print("  [journal] process one died after batch 2")

        # --- process two: replay -> resume from the watermark ---
        recovered, tag = replay_journal(jrnl)
        print(f"  [journal] replayed through batch {tag}; resuming")
        with JournalWriter(jrnl, resume=True) as journal:
            for _result in settle_stream(
                recovered,
                batches[tag + 1:],
                steps=1,
                now=START_DAY + tag + 1,
                journal=journal,
            ):
                pass
        recovered.sync()

        # Export the interchange file once, at the end.
        db = os.path.join(tmp, "service.db")
        recovered.flush_to_sqlite(db)
        rows = sqlite3.connect(db).execute(
            "SELECT COUNT(*) FROM sources"
        ).fetchone()[0]

        straight = TensorReliabilityStore()
        for _ in settle_stream(straight, batches, steps=1, now=START_DAY):
            pass
        straight.sync()
        assert recovered.list_sources() == straight.list_sources()
        assert rows == len(straight.list_sources())
        print(
            "  [journal] post-death resume == straight-through run, "
            f"record for record ({rows} rows exported)"
        )


if __name__ == "__main__":
    main()
