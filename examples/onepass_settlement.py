"""One-pass settlement: the single-HBM-sweep kernel, runnable on a laptop.

Round 14's three acts, at interpret-mode CPU shapes:

1. BIT PARITY — ``build_cycle_analytics_loop(kernel="pallas")`` (the
   Pallas kernel computing consensus + tie-break + band moments in ONE
   sweep per tile) against the multi-pass XLA fused program: every
   output family compared bit-for-bit, including the updated state.
2. THE READ DIET — per-settle HBM bytes-read (argument + temp bytes off
   AOT ``memory_analysis()`` of the same compiled programs) for the two
   routes at a big-K co-resident shape, where the 2–3 redundant sweeps
   actually cost.
3. THE SESSION SURFACE — ``settle_with_analytics(kernel="pallas")`` on a
   live resident session: settlement bytes equal the XLA default's (the
   byte-exactness coda), plus the sorted tie-break flavour
   (``AnalyticsOptions(tiebreak="sorted")``) agreeing byte-for-byte on
   exactly-representable weights.

Run from the repo root:  python examples/onepass_settlement.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

import jax
import jax.numpy as jnp

from bayesian_consensus_engine_tpu.analytics import AnalyticsOptions
from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
from bayesian_consensus_engine_tpu.parallel.sharded import (
    build_cycle_analytics_loop,
    init_block_state,
)
from bayesian_consensus_engine_tpu.pipeline import (
    ShardedSettlementSession,
    build_settlement_plan,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

# ---------------------------------------------------------------------------
# Act 1 — bit parity: one sweep vs 2-3 passes, same bits out.
# ---------------------------------------------------------------------------
MARKETS, SLOTS, STEPS = 512, 64, 3
mesh = make_mesh((1, 1), devices=jax.devices()[:1])
rng = np.random.default_rng(14)

probs = jnp.asarray(rng.random((SLOTS, MARKETS)), jnp.float32)
mask = jnp.asarray(rng.random((SLOTS, MARKETS)) < 0.85)
outcome = jnp.asarray(rng.random(MARKETS) < 0.5)
state = jax.tree.map(lambda x: x.T, init_block_state(MARKETS, SLOTS))
now0 = jnp.float32(400.0)

multi = build_cycle_analytics_loop(
    mesh, chunk_agents=16, chunk_slots=16, donate=False
)
one = build_cycle_analytics_loop(
    mesh, chunk_agents=16, chunk_slots=16, donate=False, kernel="pallas"
)
st_m, cons_m, tb_m, bands_m, _ = multi(probs, mask, outcome, state, now0, STEPS)
st_o, cons_o, tb_o, bands_o, _ = one(probs, mask, outcome, state, now0, STEPS)

families = (
    [("consensus", cons_o, cons_m)]
    + [(f"state.{n}", getattr(st_o, n), getattr(st_m, n))
       for n in st_m._fields]
    + [(f"tiebreak.{n}", getattr(tb_o, n), getattr(tb_m, n))
       for n in tb_m._fields]
    + [(f"bands.{n}", getattr(bands_o, n), getattr(bands_m, n))
       for n in bands_m._fields]
)
for name, got, want in families:
    a, b = np.asarray(got), np.asarray(want)
    assert np.array_equal(a, b, equal_nan=(a.dtype.kind == "f")), name
print(f"act 1: {len(families)} output families bit-identical "
      f"(one-pass kernel vs multi-pass XLA, {MARKETS}x{SLOTS}, "
      f"{STEPS} steps)")

# ---------------------------------------------------------------------------
# Act 2 — the read diet at a big-K co-resident shape.
# ---------------------------------------------------------------------------
# Slots dominate, AND the 16 MB VMEM budget forces the kernel to tile
# the markets axis (grid > 1) — the regime where one sweep vs 2-3
# sweeps is visible in the compiled programs' byte accounting. (At
# one-tile shapes the interpret-mode kernel degenerates to the XLA
# program and the ratio is ~1 by construction.)
M2, K2 = 1024, 512
probs2 = jnp.asarray(rng.random((K2, M2)), jnp.float32)
mask2 = jnp.asarray(rng.random((K2, M2)) < 0.9)
outcome2 = jnp.asarray(rng.random(M2) < 0.5)
state2 = jax.tree.map(lambda x: x.T, init_block_state(M2, K2))


def read_bytes(kernel):
    loop = build_cycle_analytics_loop(
        mesh, chunk_agents=256, chunk_slots=256, donate=False, kernel=kernel
    )
    mem = jax.jit(
        lambda p, ma, o, s, n: loop(p, ma, o, s, n, 1)
    ).lower(probs2, mask2, outcome2, state2, now0).compile().memory_analysis()
    return int(mem.argument_size_in_bytes + mem.temp_size_in_bytes)


multi_read = read_bytes("xla")
one_read = read_bytes("pallas")
print(f"act 2: per-settle bytes-read floor at {M2}x{K2} — "
      f"multi-pass {multi_read / 1e6:.1f} MB, "
      f"one-pass {one_read / 1e6:.1f} MB "
      f"(ratio {one_read / multi_read:.3f})")
assert one_read < multi_read

# ---------------------------------------------------------------------------
# Act 3 — the session surface + byte-exactness coda.
# ---------------------------------------------------------------------------
grid = np.round(np.linspace(0.05, 0.95, 19), 6)  # representable weights
payloads = [
    (
        f"market-{i}",
        [
            {"sourceId": f"src-{j}", "probability": float(rng.choice(grid))}
            for j in range(6)
        ],
    )
    for i in range(24)
]
outcomes = list(rng.random(24) < 0.5)


def settle(kernel=None, tiebreak=True):
    store = TensorReliabilityStore()
    plan = build_settlement_plan(store, payloads, num_slots=8)
    options = AnalyticsOptions(chunk_slots=4, tiebreak=tiebreak)
    with ShardedSettlementSession(store, plan, make_mesh()) as session:
        out = session.settle_with_analytics(
            outcomes, steps=2, now=21_900.0, analytics=options,
            kernel=kernel,
        )
    rows = np.arange(store.live_row_count())
    return out, [np.asarray(x) for x in store.host_rows(rows)]


(res_x, tb_x, _b, _p), rows_x = settle()
(res_p, tb_p, _b, _p2), rows_p = settle(kernel="pallas")
for a, b in zip(rows_p, rows_x):
    assert np.array_equal(a, b)
assert np.array_equal(
    np.asarray(res_p.consensus), np.asarray(res_x.consensus)
)
print("act 3: settle_with_analytics(kernel='pallas') — store rows and "
      "consensus byte-identical to the XLA default")

(_res_s, tb_s, _b2, _p3), rows_s = settle(tiebreak="sorted")
for name in tb_x._fields:
    assert np.array_equal(
        np.asarray(getattr(tb_s, name)), np.asarray(getattr(tb_x, name))
    ), name
for a, b in zip(rows_s, rows_x):
    assert np.array_equal(a, b)
print("act 3: tiebreak='sorted' byte-equal to the ring fold on "
      "exactly-representable weights; settlement bytes untouched")
