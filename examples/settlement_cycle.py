"""The TPU settlement loop: N consensus+update cycles in one jit dispatch.

Demonstrates the production-shaped hot path: blocked state resident on
device, outcomes judged at p >= 0.5, reliability updated with the capped
step, state carried across cycles without leaving HBM.

Run from the repo root:  python examples/settlement_cycle.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np
import jax.numpy as jnp

from bayesian_consensus_engine_tpu.parallel import (
    MarketBlockState,
    build_cycle_loop,
    init_block_state,
)

M, K = 1024, 8  # markets × source slots

rng = np.random.default_rng(0)
probs = jnp.asarray(rng.random((M, K)), dtype=jnp.float32).T      # slot-major
mask = jnp.asarray(rng.random((M, K)) < 0.9).T
outcome = jnp.asarray(rng.random(M) < 0.5)
state = MarketBlockState(*(x.T for x in init_block_state(M, K)))

loop = build_cycle_loop(mesh=None, slot_major=True, donate=True)
state, consensus = loop(probs, mask, outcome, state, jnp.float32(0.0), 30)

consensus = np.asarray(consensus)
reliability = np.asarray(state.reliability)
print(f"ran 30 cycles over {M} markets × {K} slots in one dispatch")
print(f"consensus[:5]        = {np.round(consensus[:5], 4)}")
print(f"mean reliability     = {reliability.mean():.3f} (drifted from 0.500)")
print(f"reliability extremes = {reliability.min():.2f} .. {reliability.max():.2f}")
