"""Uncertainty bands + correlated-market consensus (round 12).

The engine's reference surface emits POINT consensus; this example runs
the additive analytics tier over a small correlated-market scenario:

1. A composite market ("will EITHER leg resolve yes") and its two legs
   settle through ``ShardedSettlementSession.settle_with_analytics`` —
   cycles + tie-break + credible intervals + a damped graph sweep, ONE
   compiled program per chip against the resident reliability block.
2. The credible interval is reliability-weighted signal dispersion: a
   market whose sources agree gets a tight band, a contested one a wide
   band — at the same point consensus.
3. The graph sweep pulls the composite's consensus toward its legs'
   (damped, fixed-iteration) — an ADDITIVE scenario output; the stored
   state never sees it.
4. The byte-exactness coda: the same batch settled WITHOUT analytics
   produces the identical point consensus and identical store bytes —
   analytics on/off moves nothing (the obs on/off contract, applied to
   analytics; tests/test_analytics.py pins the full journal/SQLite
   matrix).

Run from the repo root:  python examples/uncertainty_bands.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from bayesian_consensus_engine_tpu.analytics import (
    AnalyticsOptions,
    MarketGraph,
)
from bayesian_consensus_engine_tpu.parallel.mesh import make_mesh
from bayesian_consensus_engine_tpu.pipeline import (
    ShardedSettlementSession,
    build_settlement_plan,
)
from bayesian_consensus_engine_tpu.state.tensor_store import (
    TensorReliabilityStore,
)

NOW = 21_900.0

# ---------------------------------------------------------------------------
# Act 1 — a correlated scenario: one composite, two legs, one bystander.
# ---------------------------------------------------------------------------
# The legs' sources agree tightly on leg-a, disagree hard on leg-b; the
# composite depends on both legs (weight ∝ how much each leg moves it).
payloads = [
    ("composite", [
        {"sourceId": f"s-{i}", "probability": p}
        for i, p in enumerate([0.55, 0.60, 0.50, 0.58])
    ]),
    ("leg-a", [
        {"sourceId": f"s-{i}", "probability": p}
        for i, p in enumerate([0.71, 0.70, 0.72, 0.69])
    ]),
    ("leg-b", [
        {"sourceId": f"s-{i}", "probability": p}
        for i, p in enumerate([0.15, 0.85, 0.20, 0.80])
    ]),
    ("bystander", [
        {"sourceId": f"s-{i}", "probability": p}
        for i, p in enumerate([0.40, 0.42])
    ]),
]
outcomes = [True, True, False, False]

graph = MarketGraph.from_edges(
    [
        ("composite", "leg-a", 2.0),
        ("composite", "leg-b", 1.0),
    ],
    damping=0.5,
    steps=2,
)

mesh = make_mesh()
store = TensorReliabilityStore()
plan = build_settlement_plan(store, payloads, num_slots=8)

with ShardedSettlementSession(store, plan, mesh) as session:
    result, tiebreak, bands, propagated = session.settle_with_analytics(
        outcomes, steps=2, now=NOW,
        analytics=AnalyticsOptions(graph=graph),
    )

consensus = np.asarray(result.consensus)
lo, hi = np.asarray(bands.lo), np.asarray(bands.hi)
stderr, n_eff = np.asarray(bands.stderr), np.asarray(bands.n_eff)
swept = np.asarray(propagated)

print("settle + tie-break + bands + graph sweep: ONE compiled program\n")
print(f"{'market':>10}  {'consensus':>9}  {'95% band':>17}  "
      f"{'stderr':>7}  {'n_eff':>5}  {'graph-swept':>11}")
for row, key in enumerate(result.market_keys):
    print(
        f"{key:>10}  {consensus[row]:9.4f}  "
        f"[{lo[row]:.4f}, {hi[row]:.4f}]  {stderr[row]:7.4f}  "
        f"{n_eff[row]:5.1f}  {swept[row]:11.4f}"
    )

# ---------------------------------------------------------------------------
# Act 2 — what the numbers say.
# ---------------------------------------------------------------------------
leg_a, leg_b = result.market_keys.index("leg-a"), (
    result.market_keys.index("leg-b")
)
comp = result.market_keys.index("composite")
assert hi[leg_a] - lo[leg_a] < hi[leg_b] - lo[leg_b]
print(
    "\nleg-a's sources agree (band width "
    f"{hi[leg_a] - lo[leg_a]:.4f}); leg-b is contested (width "
    f"{hi[leg_b] - lo[leg_b]:.4f}) —\nsame machinery, per-market "
    "dispersion, batched in the settle dispatch."
)
pull = 2.0 * consensus[leg_a] + 1.0 * consensus[leg_b]
pull /= 3.0
print(
    f"composite: point {consensus[comp]:.4f} pulled toward its legs' "
    f"{pull:.4f} → swept {swept[comp]:.4f}\n(damping 0.5, two sweep "
    "steps; the bystander has no edges and is untouched: "
    f"{consensus[3]:.4f} == {swept[3]:.4f})"
)
assert swept[3] == consensus[3]

# ---------------------------------------------------------------------------
# Act 3 — the byte-exactness coda: analytics moves NO settlement byte.
# ---------------------------------------------------------------------------
plain_store = TensorReliabilityStore()
plain_plan = build_settlement_plan(plain_store, payloads, num_slots=8)
with ShardedSettlementSession(plain_store, plain_plan, mesh) as plain:
    plain_result = plain.settle(outcomes, steps=2, now=NOW)

np.testing.assert_array_equal(
    consensus, np.asarray(plain_result.consensus)
)
rows = np.arange(plain_store.live_row_count())
for got, want in zip(store.host_rows(rows), plain_store.host_rows(rows)):
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
print(
    "\ncoda: point consensus and stored reliability state are "
    "BIT-IDENTICAL with\nanalytics on or off — bands, tie-break, and "
    "sweep are pure-additive reads.\nbench.py --leg e2e_analytics "
    "carries the co-residency arg-bytes capture."
)
