"""Counter-compact settlement: the cheapest way to run many cycles.

The stored state is two saturating counters per (market, slot) — see
parallel/compact.py for why the reference's update math makes that exact —
so a million-market settlement loop carries ~9 bytes/slot/step instead of
~21. This demo runs a small batch, checkpoints mid-run with orbax, resumes,
and shows the decoded state equals an uninterrupted run.

Run: python examples/compact_settlement.py
"""

import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from bayesian_consensus_engine_tpu.parallel import (  # noqa: E402
    build_compact_cycle_loop,
    compact_to_block,
    init_compact_state,
)


def main() -> None:
    markets, slots, steps = 1000, 8, 6
    rng = np.random.default_rng(0)
    probs = jnp.asarray(rng.random((slots, markets)), jnp.float32)
    mask = jnp.asarray(rng.random((slots, markets)) < 0.9)
    outcome = jnp.asarray(rng.random(markets) < 0.5)

    loop = build_compact_cycle_loop(mesh=None, donate=False)

    # Uninterrupted run.
    full_state, full_consensus = loop(
        probs, mask, outcome, init_compact_state(markets, slots),
        jnp.float32(1.0), steps,
    )

    # Interrupted: 4 cycles, checkpoint, resume for 2 more.
    mid_state, _ = loop(
        probs, mask, outcome, init_compact_state(markets, slots),
        jnp.float32(1.0), 4,
    )
    try:
        from bayesian_consensus_engine_tpu.state.checkpoint import (
            CycleCheckpointer,
        )
    except ImportError:  # orbax not installed: resume in-memory
        restored = mid_state
    else:
        with tempfile.TemporaryDirectory() as tmp:
            with CycleCheckpointer(tmp) as ckpt:
                ckpt.save(4, mid_state, meta={"next_now": 5.0}, force=True)
                restored, meta = ckpt.restore(like=mid_state)
            assert meta["next_now"] == 5.0
    resumed_state, resumed_consensus = loop(
        probs, mask, outcome, restored, jnp.float32(5.0), 2
    )

    assert np.array_equal(
        np.asarray(resumed_consensus), np.asarray(full_consensus)
    ), "resume must be bit-identical"
    for field in resumed_state._fields:
        assert np.array_equal(
            np.asarray(getattr(resumed_state, field)),
            np.asarray(getattr(full_state, field)),
        ), f"resumed state field {field} differs"
    decoded = compact_to_block(resumed_state)
    print(f"{markets} markets x {slots} slots, {steps} cycles")
    print("  state bytes/slot: 2 counters + 4 stamp = 6 (vs 12 f32)")
    print(f"  consensus[:4]   = {np.asarray(resumed_consensus)[:4].round(4)}")
    print(f"  reliability lattice values in state: "
          f"{sorted(set(np.asarray(decoded.reliability).ravel().round(6)))[:6]} ...")
    print("  checkpoint resume: bit-identical to the uninterrupted run")


if __name__ == "__main__":
    main()
